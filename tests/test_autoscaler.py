"""Self-healing elastic fleet: the SLO-driven autoscaler.

Three layers, matching the design:

* :func:`~aiko_services_tpu.orchestration.autoscaler.decide` is a PURE
  function of ``(snapshot, policy, state)`` — the unit tests below
  replay telemetry sequences and pin the exact action sequences
  (hysteresis, cooldown, backoff growth, crash-loop quarantine and its
  containment semantics, the capacity ledger's forget-surplus rule).
* :class:`~aiko_services_tpu.orchestration.autoscaler.FleetAutoscaler`
  + :class:`~aiko_services_tpu.orchestration.process_manager
  .ProcessManager` integration: REAL child processes exiting 13 drive
  the exit-code funnel into quarantine, and the ``fail_spawn`` /
  ``slow_start`` fault points hit the spawn path.
* The slow chaos gates (``slow_tests.txt``) run the full JAX serving
  rig: scripted scale-down drain under streaming load with a kill +
  failed respawn (zero lost, zero double-delivered), and the diurnal
  goodput-per-replica A/B against a static peak-sized fleet.
"""

import dataclasses
import sys
import time

import pytest

from aiko_services_tpu.orchestration.autoscaler import (
    Action, AutoscalerPolicy, ControllerState, DeathEvent,
    FleetSnapshot, PendingView, ReplicaView, decide,
)


def _policy(**overrides) -> AutoscalerPolicy:
    """Deterministic test policy: SLO scaling frozen unless a test
    opts in (huge windows), tight backoff."""
    defaults = dict(target=1, min_replicas=1, max_replicas=8,
                    backoff_base_s=1.0, backoff_cap_s=8.0,
                    cooldown_s=10.0,
                    breach_windows=10 ** 6, clear_windows=10 ** 6,
                    crash_loop_threshold=3, crash_loop_window_s=60.0,
                    quarantine_s=300.0)
    defaults.update(overrides)
    return AutoscalerPolicy(**defaults)


def _live(slot, **kw) -> ReplicaView:
    return ReplicaView(slot=slot, **kw)


# ---------------------------------------------------------------- #
# decide(): bootstrap & self-healing
# ---------------------------------------------------------------- #

def test_bootstrap_spawns_to_target():
    actions, state = decide(FleetSnapshot(now=0.0), _policy(target=2))
    assert [a.kind for a in actions] == ["spawn", "spawn"]
    assert [a.slot for a in actions] == ["decode1", "decode2"]
    assert all(a.reason == "scale_out" for a in actions)
    assert state.targets == {"decode": 2}
    assert state.slots == {"decode1": "decode", "decode2": "decode"}


def test_replace_dead_slot_after_backoff():
    policy = _policy()
    # Adopt a live replica, then watch it die.
    actions, state = decide(
        FleetSnapshot(now=0.0, replicas=(_live("decode1"),)), policy)
    assert actions == []

    # Death at t=10: backoff (base 1s) gates the respawn.
    actions, state = decide(FleetSnapshot(
        now=10.0, deaths=(DeathEvent("decode1", ts=10.0),)),
        policy, state)
    assert actions == []
    assert state.backoff_until["decode1"] == pytest.approx(11.0)

    actions, state = decide(FleetSnapshot(now=10.5), policy, state)
    assert actions == []                       # still backing off

    actions, state = decide(FleetSnapshot(now=11.0), policy, state)
    assert actions == [Action("spawn", "decode1", role="decode",
                              reason="replace")]

    # Second death doubles the backoff: base * 2^(2-1).
    actions, state = decide(FleetSnapshot(
        now=20.0, deaths=(DeathEvent("decode1", ts=20.0),)),
        policy, state)
    assert actions == []
    assert state.backoff_until["decode1"] == pytest.approx(22.0)


def test_pending_spawn_is_not_down():
    """A spawn in flight must not trigger a duplicate replacement."""
    policy = _policy()
    _, state = decide(FleetSnapshot(now=0.0), policy)   # spawns decode1
    actions, state = decide(FleetSnapshot(
        now=1.0, pending=(PendingView("decode1", due=30.0),)),
        policy, state)
    assert actions == []


def test_expected_death_ends_the_slot():
    """Drain-completion termination is bookkeeping, not a crash: the
    slot is forgotten, never respawned."""
    state = ControllerState(
        targets={"decode": 1},
        slots={"decode1": "decode", "decode2": "decode"})
    actions, state = decide(FleetSnapshot(
        now=5.0, replicas=(_live("decode2"),),
        deaths=(DeathEvent("decode1", ts=5.0, expected=True),)),
        _policy(), state)
    assert "decode1" not in state.slots
    assert actions == []
    assert "decode1" not in state.deaths


def test_fresh_slot_names_skip_adopted_squatters():
    """An adopted replica may already be called ``decode1``; new
    capacity must not collide with it."""
    actions, state = decide(
        FleetSnapshot(now=0.0, replicas=(_live("decode1"),)),
        _policy(target=2))
    assert actions == [Action("spawn", "decode2", role="decode",
                              reason="scale_out")]
    assert set(state.slots) == {"decode1", "decode2"}


# ---------------------------------------------------------------- #
# decide(): crash-loop quarantine & containment
# ---------------------------------------------------------------- #

def _quarantine_decode1(policy):
    """Drive decode1 through 3 deaths inside the window; decode2 stays
    live throughout."""
    _, state = decide(
        FleetSnapshot(now=0.0, replicas=(_live("decode1"),
                                         _live("decode2"))), policy)
    actions = []
    for ts in (10.0, 12.0, 14.0):
        actions, state = decide(FleetSnapshot(
            now=ts, replicas=(_live("decode2"),),
            deaths=(DeathEvent("decode1", ts=ts, exit_code=13),)),
            policy, state)
    return actions, state


def test_crash_loop_quarantine_contains_the_slot():
    policy = _policy(target=2)
    actions, state = _quarantine_decode1(policy)
    assert [a.kind for a in actions] == ["quarantine"]
    assert "exit=13" in actions[0].reason
    assert "decode1" in state.quarantined
    assert state.quarantined["decode1"] == pytest.approx(14.0 + 300.0)

    # Containment: the quarantined slot pads the ledger — no backfill
    # spawn, no respawn, and decode2 (the last healthy replica) is
    # NEVER drained on the zombie's behalf.
    actions, state = decide(FleetSnapshot(
        now=20.0, replicas=(_live("decode2"),)), policy, state)
    assert actions == []
    actions, state = decide(FleetSnapshot(
        now=40.0, replicas=(_live("decode2"),)), policy, state)
    assert actions == []


def test_quarantine_expiry_forgets_surplus_slot():
    """When the quarantine lapses and the target no longer wants the
    capacity, the slot is forgotten outright — not respawned just to
    be drained again."""
    state = ControllerState(
        targets={"decode": 1},
        slots={"decode1": "decode", "decode2": "decode"},
        quarantined={"decode1": 314.0})
    actions, state = decide(FleetSnapshot(
        now=315.0, replicas=(_live("decode2"),)), _policy(), state)
    assert actions == []
    assert state.quarantined == {}
    assert "decode1" not in state.slots        # forgotten, not respawned
    assert list(state.slots) == ["decode2"]


def test_draining_replica_counts_out_of_eventual_capacity():
    """While a drain is in flight the fleet's EVENTUAL size already
    excludes it: no replacement is spawned and no second drain fires."""
    state = ControllerState(
        targets={"decode": 1},
        slots={"decode1": "decode", "decode2": "decode"})
    actions, state = decide(FleetSnapshot(
        now=5.0, replicas=(_live("decode1", retiring=True),
                           _live("decode2"))), _policy(), state)
    assert actions == []


# ---------------------------------------------------------------- #
# decide(): SLO scaling — hysteresis, cooldown, scale-in
# ---------------------------------------------------------------- #

def test_scale_out_needs_consecutive_breaches_and_cooldown():
    policy = _policy(breach_windows=3, cooldown_s=10.0)
    _, state = decide(FleetSnapshot(now=0.0), policy)   # decode1
    fleet = (_live("decode1"),)

    # Two breach ticks: hysteresis holds the target.
    for now in (1.0, 2.0):
        actions, state = decide(FleetSnapshot(
            now=now, replicas=fleet, ttft_p95_ms=900.0), policy, state)
        assert state.targets == {"decode": 1}

    # Third consecutive breach scales out.
    actions, state = decide(FleetSnapshot(
        now=3.0, replicas=fleet, ttft_p95_ms=900.0), policy, state)
    assert state.targets == {"decode": 2}
    assert [a for a in actions if a.kind == "spawn"] == \
        [Action("spawn", "decode2", role="decode", reason="scale_out")]

    # Still breaching, but the cooldown blocks a second raise...
    fleet = (_live("decode1"), _live("decode2"))
    for now in (4.0, 5.0, 6.0, 9.0):
        actions, state = decide(FleetSnapshot(
            now=now, replicas=fleet, ttft_p95_ms=900.0), policy, state)
        assert state.targets == {"decode": 2}

    # ...until it expires (last scale at t=3, cooldown 10).
    actions, state = decide(FleetSnapshot(
        now=13.0, replicas=fleet, ttft_p95_ms=900.0), policy, state)
    assert state.targets == {"decode": 3}


def test_shed_delta_counts_as_breach():
    policy = _policy(breach_windows=1, cooldown_s=0.0)
    _, state = decide(FleetSnapshot(now=0.0), policy)
    actions, state = decide(FleetSnapshot(
        now=1.0, replicas=(_live("decode1"),), shed_delta=4),
        policy, state)
    assert state.targets == {"decode": 2}


def test_scale_in_drains_the_idlest_replica():
    policy = _policy(target=2, clear_windows=3, cooldown_s=0.0)
    fleet = (_live("decode1", slots_active=1), _live("decode2"))
    # The bootstrap decide already counts clear tick #1.
    _, state = decide(FleetSnapshot(now=0.0, replicas=fleet), policy)
    actions, state = decide(FleetSnapshot(now=1.0, replicas=fleet),
                            policy, state)
    assert state.targets == {"decode": 2}      # two clear ticks so far
    actions, state = decide(FleetSnapshot(now=2.0, replicas=fleet),
                            policy, state)
    assert state.targets == {"decode": 1}
    assert actions == [Action("drain", "decode2", role="decode",
                              reason="scale_in")]   # idlest wins


def test_scale_in_blocked_by_queue_pending_and_floor():
    # Queued work blocks scale-in even after the clear streak.  The
    # bootstrap decide counts clear tick #1, so by t=1 the streak is
    # already past the window — only the queue holds the target.
    policy = _policy(target=2, clear_windows=2, cooldown_s=0.0)
    _, state = decide(FleetSnapshot(now=0.0, replicas=(
        _live("decode1", queue_depth=3), _live("decode2"))), policy)
    actions, state = decide(FleetSnapshot(
        now=1.0, replicas=(_live("decode1", queue_depth=3),
                           _live("decode2"))), policy, state)
    assert state.targets == {"decode": 2}

    # A pending spawn blocks it too (fleet still in motion).
    actions, state = decide(FleetSnapshot(
        now=2.0, replicas=(_live("decode1"), _live("decode2")),
        pending=(PendingView("decode3", due=30.0),)), policy, state)
    assert state.targets == {"decode": 2}

    # And min_replicas is a hard floor.
    policy_floor = _policy(target=1, clear_windows=1, cooldown_s=0.0)
    _, state = decide(FleetSnapshot(now=0.0,
                                    replicas=(_live("decode1"),)),
                      policy_floor)
    for now in (1.0, 2.0, 3.0):
        actions, state = decide(FleetSnapshot(
            now=now, replicas=(_live("decode1"),)), policy_floor, state)
        assert state.targets == {"decode": 1}
        assert actions == []


def test_disaggregated_breach_attribution():
    """TTFT breaches grow the prefill pool, shed breaches decode."""
    policy = _policy(target=1, prefill_target=1, prefill_max=4,
                     breach_windows=1, cooldown_s=0.0)
    _, state = decide(FleetSnapshot(now=0.0), policy)
    assert state.targets == {"decode": 1, "prefill": 1}
    prefill_slot = next(s for s, r in state.slots.items()
                        if r == "prefill")
    fleet = (_live("decode1"), _live(prefill_slot, role="prefill"))

    actions, state = decide(FleetSnapshot(
        now=1.0, replicas=fleet, ttft_p95_ms=900.0), policy, state)
    assert state.targets == {"decode": 1, "prefill": 2}
    spawned = [a for a in actions if a.kind == "spawn"]
    assert [a.role for a in spawned] == ["prefill"]

    actions, state = decide(FleetSnapshot(
        now=2.0, replicas=fleet, shed_delta=5), policy, state)
    assert state.targets == {"decode": 2, "prefill": 2}


def test_decide_is_pure_and_deterministic():
    policy = _policy(target=2, breach_windows=1, cooldown_s=0.0)
    state = ControllerState(
        targets={"decode": 2},
        slots={"decode1": "decode", "decode2": "decode"},
        deaths={"decode1": [3.0]}, backoff_until={"decode1": 4.0})
    snapshot = FleetSnapshot(
        now=9.0, replicas=(_live("decode2", queue_depth=1),),
        deaths=(DeathEvent("decode2", ts=9.0),), ttft_p95_ms=800.0)
    frozen = dataclasses.asdict(state)

    first_actions, first_state = decide(snapshot, policy, state)
    second_actions, second_state = decide(snapshot, policy, state)
    assert dataclasses.asdict(state) == frozen     # input untouched
    assert first_actions == second_actions
    assert dataclasses.asdict(first_state) == \
        dataclasses.asdict(second_state)


# ---------------------------------------------------------------- #
# FleetAutoscaler actor: wire commands & fault points
# ---------------------------------------------------------------- #

def _make_autoscaler(engine, policy, spawner=None, terminator=None,
                     tick_s=0.05, broker="asc"):
    from aiko_services_tpu.orchestration.autoscaler import (
        FleetAutoscaler,
    )
    from aiko_services_tpu.runtime import (
        Process, actor_args, compose_instance,
    )
    process = Process(namespace="asc", hostname="h", pid="1",
                      engine=engine, broker=broker)
    return compose_instance(
        FleetAutoscaler, actor_args("autoscaler"), process=process,
        spawner=spawner, terminator=terminator, policy=policy,
        tick_s=tick_s)


def test_scale_target_wire_command_clamps(engine):
    policy = _policy(target=1, max_replicas=4, prefill_max=2)
    autoscaler = _make_autoscaler(engine, policy)

    autoscaler._wire_scale_target("9")
    assert autoscaler.state.targets["decode"] == 4     # clamped to cap
    assert autoscaler.share["target_decode"] == 4
    autoscaler._wire_scale_target("prefill", "2")
    assert autoscaler.state.targets["prefill"] == 2
    autoscaler._wire_scale_target("warp", "3")          # unknown role
    autoscaler._wire_scale_target("not_a_number")       # junk value
    assert autoscaler.state.targets == {"decode": 4, "prefill": 2}


def test_fail_spawn_fault_reports_through_death_funnel(engine):
    """``fail_spawn`` must fail the launch WITHOUT calling the
    spawner, feed the same funnel as a real spawn failure, and let
    backoff drive the retry (which succeeds once the rule is spent)."""
    from aiko_services_tpu.runtime import faults

    calls = []
    policy = _policy(target=1, backoff_base_s=0.2)
    autoscaler = _make_autoscaler(
        engine, policy, spawner=lambda slot, role: calls.append(slot),
        broker="failspawn")
    faults.install(faults.FaultPlan().add("fail_spawn", nth=1))
    try:
        for _ in range(40):
            engine.advance(0.05)
            if calls:
                break
    finally:
        faults.uninstall()
    assert calls == ["decode1"]               # only the RETRY launched
    assert autoscaler.counters["spawn_failures"] == 1
    assert autoscaler.counters["respawns"] == 1
    assert autoscaler.counters["spawns"] == 0


def test_slow_start_fault_delays_the_launch(engine):
    from aiko_services_tpu.runtime import faults

    calls = []
    autoscaler = _make_autoscaler(
        engine, _policy(target=1),
        spawner=lambda slot, role: calls.append(slot),
        broker="slowstart")
    faults.install(faults.FaultPlan().add("slow_start", nth=1, ms=500))
    try:
        engine.advance(0.05)                  # first tick: spawn decided
        assert autoscaler.counters["slow_starts"] == 1
        assert "decode1" in autoscaler._pending
        assert calls == []                    # held by the delay
        engine.advance(0.3)
        assert calls == []
        engine.advance(0.3)                   # past the 0.5s delay
        assert calls == ["decode1"]
    finally:
        faults.uninstall()


# ---------------------------------------------------------------- #
# ProcessManager integration: exit codes -> crash-loop quarantine
# ---------------------------------------------------------------- #

def test_exit_13_crash_loop_quarantines_and_stops_respawning(engine):
    """Satellite 4's quarantine gate with REAL child processes: a slot
    whose child exits 13 three times is quarantined — the supervisor
    stops feeding the crash loop — and ``(clear_quarantine)`` resumes
    it."""
    from aiko_services_tpu.orchestration.autoscaler import (
        manager_spawner, manager_terminator,
    )
    from aiko_services_tpu.orchestration.process_manager import (
        ProcessManager,
    )

    policy = _policy(target=1, backoff_base_s=0.1, backoff_cap_s=0.4,
                     crash_loop_threshold=3, crash_loop_window_s=60.0,
                     spawn_timeout_s=30.0)
    autoscaler = _make_autoscaler(engine, policy, broker="crashloop")
    manager = ProcessManager(exit_handler=autoscaler.note_exit,
                             engine=engine)
    autoscaler._spawner = manager_spawner(
        manager, sys.executable,
        argv_fn=lambda slot, role: ["-c", "import sys; sys.exit(13)"])
    autoscaler._terminator = manager_terminator(manager)

    def pump(predicate, what, real_timeout_s=60.0):
        deadline = time.time() + real_timeout_s
        while not predicate():
            assert time.time() < deadline, what
            engine.advance(0.05)              # virtual timers
            time.sleep(0.005)                 # real child lifecycles

    try:
        pump(lambda: "decode1" in autoscaler.state.quarantined,
             "slot never quarantined")
        assert autoscaler.counters["quarantines"] == 1
        assert autoscaler.counters["deaths_observed"] == 3
        assert manager.exit_codes["decode1"] == 13
        assert autoscaler.share["quarantine"] == "decode1"

        # Containment: no further launches while quarantined.
        launches = (autoscaler.counters["spawns"]
                    + autoscaler.counters["respawns"])
        for _ in range(40):
            engine.advance(0.05)
            time.sleep(0.002)
        assert (autoscaler.counters["spawns"]
                + autoscaler.counters["respawns"]) == launches
        assert autoscaler.share["replicas_live"] == 0

        # Operator override resumes the respawn loop.
        autoscaler._wire_clear_quarantine("decode1")
        assert autoscaler.state.quarantined == {}
        pump(lambda: (autoscaler.counters["spawns"]
                      + autoscaler.counters["respawns"]) > launches,
             "no respawn after clear_quarantine")
    finally:
        manager.terminate_all(kill=True)


# ---------------------------------------------------------------- #
# Diurnal workload trace (satellite: loadgen)
# ---------------------------------------------------------------- #

def test_diurnal_trace_is_seeded_and_bounded():
    from aiko_services_tpu.tools.loadgen import diurnal_trace

    times = diurnal_trace(20.0, base_hz=2.0, peak_hz=10.0,
                          period_s=5.0, seed=1)
    assert times == diurnal_trace(20.0, base_hz=2.0, peak_hz=10.0,
                                  period_s=5.0, seed=1)
    assert times != diurnal_trace(20.0, base_hz=2.0, peak_hz=10.0,
                                  period_s=5.0, seed=2)
    assert times == sorted(times)
    assert all(0.0 <= t < 20.0 for t in times)
    # E[arrivals] = ∫rate = 20·(2 + 8·0.5) = 120; allow wide Poisson
    # slack but reject a flat-rate or empty trace.
    assert 60 < len(times) < 200

    bursty = diurnal_trace(20.0, base_hz=2.0, peak_hz=10.0,
                           period_s=5.0, burst_hz=40.0,
                           burst_every_s=5.0, burst_len_s=0.5, seed=1)
    assert bursty == sorted(bursty)
    assert len(bursty) > len(times)           # bursts add arrivals


def test_goodput_accounting():
    from aiko_services_tpu.tools.loadgen import LoadReport

    report = LoadReport(
        sent=10, completed=8, errors=2, timeouts=0, elapsed_s=4.0,
        latencies_ms=[10.0] * 8,
        ttfts_ms=[100.0, 100.0, 100.0, 100.0, 100.0, 900.0],
        slo_ttft_ms=500.0, replica_seconds=8.0)
    # 5 within-SLO + 2 unstamped completions count as good; the 900ms
    # breach does not.
    assert report.good_completions == 7
    assert report.goodput_rps == pytest.approx(7 / 4.0)
    assert report.avg_replicas == pytest.approx(2.0)
    assert report.goodput_per_replica == pytest.approx(7 / 8.0)
    assert "goodput" in repr(report)


# ---------------------------------------------------------------- #
# Chaos gates (slow: full JAX serving rig — see slow_tests.txt)
# ---------------------------------------------------------------- #

def test_elastic_chaos_drain_loses_nothing():
    """ISSUE acceptance: scripted scale-down drain under streaming
    load, with a kill during the drain window and a failed + slowed
    replacement spawn — the fleet converges to the target and no
    request is lost, duplicated, or re-streamed."""
    from aiko_services_tpu.tools.loadgen import run_elastic_chaos

    report = run_elastic_chaos(seed=0, duration_s=8.0)
    assert report.lost == 0, report
    assert report.timeouts == 0, report
    assert report.duplicate_finals == 0, report
    stats = report.server_stats
    assert stats["stream_mismatches"] == 0    # partials == final, once
    assert stats["converged"] is True
    assert stats["drains"] >= 1
    assert stats["drain_completed"] >= 1
    assert stats["spawn_failures"] >= 1       # fail_spawn fired
    assert stats["slow_starts"] >= 1          # slow_start fired
    assert stats["deaths_observed"] >= 2      # kill + failed respawn
    assert stats["faults_fired"] >= 3         # the schedule really ran
    assert stats["replicas_live"] == sum(stats["targets"].values())


def test_diurnal_autoscaled_beats_static_peak_goodput():
    """ISSUE acceptance: over a diurnal day the autoscaled fleet's
    goodput PER REPLICA strictly beats a static fleet sized for the
    peak — serving the valleys with fewer replicas is the point."""
    from aiko_services_tpu.tools.loadgen import run_elastic

    knobs = dict(duration_s=16.0, seed=2, base_hz=1.0, peak_hz=8.0,
                 period_s=8.0, slo_ttft_ms=500.0, warmup=4)
    autoscaled = run_elastic(**knobs)
    static = run_elastic(static_replicas=3, **knobs)
    assert autoscaled.lost == 0 and autoscaled.timeouts == 0
    assert static.lost == 0 and static.timeouts == 0
    assert autoscaled.avg_replicas < 3.0
    assert autoscaled.goodput_per_replica > static.goodput_per_replica

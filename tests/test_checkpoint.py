"""Checkpoint/resume: orbax-backed train state, cross-topology restore,
and host-side stream continuations (SURVEY.md §5.4 — absent in the
reference, designed fresh here)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from aiko_services_tpu.models import llama
from aiko_services_tpu.parallel.checkpoint import (
    StreamCheckpoint, TrainCheckpointer,
    load_stream_checkpoint, save_stream_checkpoint)
from aiko_services_tpu.parallel.mesh import make_mesh
from aiko_services_tpu.parallel.train import init_train_state


CONFIG = llama.LlamaConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=32)


def _state(seed=0):
    optimizer = optax.adam(1e-3)
    params, opt_state = init_train_state(
        CONFIG, jax.random.PRNGKey(seed), optimizer)
    return params, opt_state


def test_save_restore_roundtrip(tmp_path):
    params, opt_state = _state()
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), max_to_keep=2)
    bumped = jax.tree.map(lambda x: x + 1, params)
    ckpt.save(0, {"params": params}, metadata={"tokens_seen": 123})
    ckpt.save(1, {"params": bumped})

    out = ckpt.restore({"params": params})
    assert out["step"] == 1
    got = out["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        got, bumped)

    out0 = ckpt.restore({"params": params}, step=0)
    assert out0["metadata"]["tokens_seen"] == 123
    ckpt.close()


def test_retention_policy(tmp_path):
    params, _ = _state()
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), max_to_keep=2)
    for step in range(4):
        ckpt.save(step, {"params": params})
    assert ckpt.all_steps() == [2, 3]
    ckpt.close()


def test_cross_topology_restore(tmp_path):
    """Save sharded on dp=2×tp=4, restore onto dp=4×tp=2."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    params, _ = _state()
    specs = llama.param_specs(CONFIG)

    mesh_a = make_mesh(dp=2, tp=4)
    from jax.sharding import NamedSharding
    sharded = jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh_a, spec)),
        params, specs, is_leaf=lambda x: not isinstance(x, (dict, list)))

    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(7, {"params": sharded})

    mesh_b = make_mesh(dp=4, tp=2)
    out = ckpt.restore({"params": params}, mesh=mesh_b,
                       specs={"params": specs})
    restored = out["params"]

    flat_r, _ = jax.tree_util.tree_flatten(restored)
    flat_o, _ = jax.tree_util.tree_flatten(params)
    for a, b in zip(flat_r, flat_o):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
        # every restored leaf is addressable on mesh_b's devices
        assert set(d.id for d in a.sharding.device_set) <= {
            d.id for d in mesh_b.devices.flat}
    ckpt.close()


def test_opt_state_tuple_structured_restore(tmp_path):
    """optax opt_state is a tuple of NamedTuples — sharded restore must
    recurse through it, not treat it as a leaf."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from jax.sharding import PartitionSpec as P
    params, opt_state = _state()
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(0, {"opt_state": opt_state})

    mesh = make_mesh(dp=8)
    opt_specs = jax.tree.map(lambda _: P(), opt_state)
    out = ckpt.restore({"opt_state": opt_state}, mesh=mesh,
                       specs={"opt_state": opt_specs})
    flat_r, tdef_r = jax.tree_util.tree_flatten(out["opt_state"])
    flat_o, tdef_o = jax.tree_util.tree_flatten(opt_state)
    assert len(flat_r) == len(flat_o)
    for a, b in zip(flat_r, flat_o):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_reserved_state_names_rejected(tmp_path):
    params, _ = _state()
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    with pytest.raises(ValueError):
        ckpt.save(0, {"metadata": params})
    with pytest.raises(ValueError):
        ckpt.restore({"step": params})
    ckpt.close()


def test_restore_missing_raises(tmp_path):
    params, _ = _state()
    ckpt = TrainCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore({"params": params})
    ckpt.close()


def test_stream_checkpoint_roundtrip(tmp_path):
    class FakeStream:
        stream_id = "s7"
        frame_id = 42
        graph_path = "main"
        parameters = {"rate": 10, "bad": object()}
        variables = {"cursor": 5}

    swag = {"text": "hello", "array": np.zeros((2, 2))}
    path = save_stream_checkpoint(str(tmp_path), FakeStream(), swag)
    rec = load_stream_checkpoint(str(tmp_path), "s7")
    assert isinstance(rec, StreamCheckpoint)
    assert rec.frame_id == 42
    assert rec.parameters == {"rate": 10}      # non-JSON entry dropped
    assert rec.swag == {"text": "hello"}       # array dropped (device state)
    assert rec.graph_path == "main"


def test_async_save_overlaps_and_restores(tmp_path):
    """async_save=True returns before the write commits; wait() (or a
    later save) barriers, and the restored tree is identical."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from aiko_services_tpu.parallel.checkpoint import TrainCheckpointer

    state = {"params": {"w": jnp.arange(12.0).reshape(3, 4),
                        "b": jnp.ones((4,))}}
    ckpt = TrainCheckpointer(str(tmp_path / "async"), max_to_keep=2,
                             async_save=True)
    assert ckpt.save(1, state)
    ckpt.wait()
    assert ckpt.latest_step() == 1
    restored = ckpt.restore(
        {"params": jax.tree.map(np.zeros_like, state["params"])})
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    # Second async save supersedes, retention keeps both steps.
    state2 = {"params": jax.tree.map(lambda x: x + 1, state["params"])}
    assert ckpt.save(2, state2)
    ckpt.wait()
    assert ckpt.latest_step() == 2
    ckpt.close()


def test_elastic_trainer_topology_change_matches_uninterrupted(tmp_path):
    """Train 4 steps on dp=8, 'lose chips', resume on dp=4 x tp=2:
    losses for steps 5-8 equal an uninterrupted 8-step dp=8 run (up to
    bf16 reduction order)."""
    import jax
    import numpy as np
    import optax

    from aiko_services_tpu.models import llama
    from aiko_services_tpu.parallel import make_mesh
    from aiko_services_tpu.parallel.elastic import ElasticTrainer

    config = llama.CONFIGS["tiny"]
    rng = np.random.default_rng(0)
    all_batches = [rng.integers(0, config.vocab_size, (8, 16))
                   .astype(np.int32) for _ in range(8)]

    def optimizer():
        return optax.adamw(1e-3)

    # Uninterrupted baseline.
    base = ElasticTrainer(config, optimizer(), str(tmp_path / "base"),
                          make_mesh(dp=8), save_every=0, seed=7)
    base_losses = base.run(all_batches)
    base.close()

    # Elastic: 4 steps on dp=8, checkpoint, resume on dp=4 x tp=2.
    directory = str(tmp_path / "elastic")
    first = ElasticTrainer(config, optimizer(), directory,
                           make_mesh(dp=8), save_every=4, seed=7)
    first_losses = first.run(all_batches[:4])
    assert first.step == 4
    first.close()

    second = ElasticTrainer(config, optimizer(), directory,
                            make_mesh(dp=4, tp=2), save_every=4, seed=99)
    assert second.step == 4          # resumed, seed ignored
    second_losses = second.run(all_batches[4:])
    second.close()

    for a, b in zip(base_losses, first_losses + second_losses):
        assert abs(a - b) < 5e-3, (base_losses,
                                   first_losses + second_losses)


def test_quantized_param_tree_roundtrip(tmp_path):
    """Serving deployment shape: int8 and int4 quantized weight trees
    (int8 codes + f32 scales, nibble-packed q4) checkpoint and restore
    bit-exactly — a replica can boot from a quantized checkpoint
    without requantizing."""
    import jax
    import numpy as np
    from aiko_services_tpu.models import llama
    from aiko_services_tpu.parallel.checkpoint import TrainCheckpointer

    config = llama.CONFIGS["tiny"]
    dense = llama.init_params(config, jax.random.PRNGKey(0))
    for bits in (8, 4):
        quantized = llama.quantize_params(dense, bits=bits)
        directory = tmp_path / f"int{bits}"
        saver = TrainCheckpointer(str(directory))
        saver.save(1, {"params": quantized}, metadata={"bits": bits})
        saver.close()

        loader = TrainCheckpointer(str(directory))
        restored = loader.restore({"params": quantized})["params"]
        loader.close()
        flat_a = jax.tree.leaves(quantized)
        flat_b = jax.tree.leaves(restored)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # The restored tree decodes: one greedy step runs finite.
        logits = llama.forward(
            restored, jax.numpy.zeros((1, 8), jax.numpy.int32), config)
        assert np.isfinite(np.asarray(logits)).all()

"""Process/Service/Actor tests over the loopback transport.

Multi-"process" scenarios run several Process instances against one
shared loopback broker within a single event engine — the in-process
equivalent of the reference's many-OS-processes + mosquitto setup.
"""

import pytest

from aiko_services_tpu.runtime import (
    Actor, Process, ServiceFilter, ServiceFields, ServiceTags,
    ServiceTopicPath, Services, actor_args, compose_instance,
    get_actor_proxy,
)
from aiko_services_tpu.runtime.event import EventEngine, VirtualClock


@pytest.fixture()
def process(engine):
    return Process(namespace="test", hostname="h", pid="1",
                   engine=engine, broker="t")


class Greeter(Actor):
    def __init__(self, context, process=None):
        super().__init__(context, process)
        self.greetings = []
        self.controls = []

    def aloha(self, name):
        self.greetings.append(name)

    def ctl(self, value):
        self.controls.append(value)


def test_service_identity(process):
    actor = compose_instance(Greeter, actor_args("greeter"), process=process)
    assert actor.topic_path == "test/h/1/1"
    assert actor.topic_in == "test/h/1/1/in"
    assert actor.topic_state == "test/h/1/1/state"
    second = compose_instance(Greeter, actor_args("g2"), process=process)
    assert second.service_id == 2


def test_actor_command_dispatch(process, engine):
    actor = compose_instance(Greeter, actor_args("greeter"), process=process)
    process.message.publish(actor.topic_in, "(aloha Pele)")
    engine.drain()
    assert actor.greetings == ["Pele"]


def test_control_mailbox_priority(process, engine):
    from aiko_services_tpu.runtime.actor import ActorMessage, Mailbox
    actor = compose_instance(Greeter, actor_args("greeter"), process=process)
    order = []
    actor.aloha = lambda name: order.append(("in", name))
    actor.ctl = lambda v: order.append(("control", v))
    actor._post_message(Mailbox.IN, ActorMessage("aloha", ["a"]))
    actor._post_message(Mailbox.CONTROL, ActorMessage("ctl", ["b"]))
    engine.drain()
    # Control message processed first despite being posted second.
    assert order == [("control", "b"), ("in", "a")]


def test_actor_share_is_ec_backed(process, engine):
    """Every Actor auto-creates an ECProducer on its share dict; remote
    (update …) on the control topic mutates it (reference actor.py:199-205)."""
    actor = compose_instance(Greeter, actor_args("greeter"), process=process)
    assert actor.ec_producer is not None
    process.message.publish(actor.topic_control, "(update log_level DEBUG)")
    engine.drain()
    assert actor.share["log_level"] == "DEBUG"


def test_unknown_and_private_commands_ignored(process, engine):
    actor = compose_instance(Greeter, actor_args("greeter"), process=process)
    process.message.publish(actor.topic_in, "(nonexistent x)")
    process.message.publish(actor.topic_in, "(_post_message hack)")
    process.message.publish(actor.topic_in, "not even an s-expression (")
    engine.drain()  # nothing raises, nothing dispatched
    assert actor.greetings == []


def test_remote_proxy_rpc(engine):
    """Two processes on one broker: caller proxies callee's interface."""
    p1 = Process(namespace="test", hostname="h", pid="1",
                 engine=engine, broker="t")
    p2 = Process(namespace="test", hostname="h", pid="2",
                 engine=engine, broker="t")
    callee = compose_instance(Greeter, actor_args("callee"), process=p2)
    proxy = get_actor_proxy(callee.topic_path, Greeter, p1)
    proxy.aloha("Honua")
    engine.drain()
    assert callee.greetings == ["Honua"]


def test_registrar_bootstrap_announce(engine):
    """A process announces services when a registrar primary appears."""
    p = Process(namespace="test", hostname="h", pid="1",
                engine=engine, broker="t")
    compose_instance(Greeter, actor_args("greeter", protocol="greet:0"),
                     process=p)
    seen = []
    # Fake registrar: watch its /in topic.
    from aiko_services_tpu.transport import LoopbackMessage
    reg = LoopbackMessage(lambda t, pl: seen.append(pl), broker="t")
    reg.subscribe("test/h/99/1/in")
    reg.publish("test/service/registrar",
                "(primary found test/h/99/1 2 0)", retain=True)
    engine.drain()
    assert any(s.startswith("(add test/h/1/1 greeter greet:0")
               for s in seen), seen


def test_services_collection_and_filters():
    services = Services()
    f1 = ServiceFields("ns/h/1/1", "alpha", "proto:0", "loopback", "me",
                       ["a=1"])
    f2 = ServiceFields("ns/h/1/2", "beta", "other:0", "loopback", "me",
                       ["a=2"])
    f3 = ServiceFields("ns/h/2/1", "alpha", "proto:0", "loopback", "you",
                       ["a=1"])
    for f in (f1, f2, f3):
        services.add(f)
    assert len(services) == 3
    assert services.get("ns/h/1/2").name == "beta"
    assert [f.name for f in services.filter(ServiceFilter(name="alpha"))] \
        == ["alpha", "alpha"]
    assert [f.topic_path for f in
            services.filter(ServiceFilter(protocol="proto"))] \
        == ["ns/h/1/1", "ns/h/2/1"]
    assert [f.topic_path for f in
            services.filter(ServiceFilter(tags=["a=1"], owner="me"))] \
        == ["ns/h/1/1"]
    removed = services.remove_process("ns/h/1")
    assert {f.name for f in removed} == {"alpha", "beta"}
    assert len(services) == 1


def test_service_topic_path_parse():
    tp = ServiceTopicPath.parse("ns/host/123/4")
    assert tp.process_path == "ns/host/123"
    assert tp.terse == "host/123/4"
    assert str(tp) == "ns/host/123/4"
    assert ServiceTopicPath.parse("too/short") is None


def test_service_tags():
    assert ServiceTags.parse(["a=1", "b=2"]) == {"a": "1", "b": "2"}
    assert ServiceTags.generate({"a": "1"}) == ["a=1"]
    assert ServiceTags.match(["a=1", "b=2"], ["a=1"])
    assert not ServiceTags.match(["a=1"], ["b=2"])
    assert ServiceTags.match(["a=1"], ["*"])

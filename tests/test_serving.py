"""DP replica serving: router discovery, round-robin, failover, and the
llama replica end-to-end (loopback broker, virtual clock)."""

import numpy as np

from aiko_services_tpu.orchestration.serving import (
    ModelReplica, ReplicaRouter, make_llama_infer,
    make_speculative_infer,
)
from aiko_services_tpu.pipeline.codec import decode_swag, encode_swag
from aiko_services_tpu.registry import Registrar
from aiko_services_tpu.runtime import (
    Process, actor_args, compose_instance,
)
from aiko_services_tpu.utils.sexpr import generate, parse


def make_process(engine, pid, broker="serve"):
    return Process(namespace="test", hostname="h", pid=str(pid),
                   engine=engine, broker=broker)


def collect_responses(process, topic, into):
    def handler(_topic, payload):
        command, params = parse(payload)
        if command == "infer_response":
            into.append((params[0], decode_swag(params[1])))
    process.add_message_handler(handler, topic)


def test_round_robin_and_failover(engine):
    p0 = make_process(engine, 1)
    Registrar(process=p0)
    engine.advance(4.0)

    replica_procs, replicas = [], []
    for i in range(3):
        p = make_process(engine, 10 + i)
        replica = compose_instance(
            ModelReplica, actor_args(f"replica_{i}"), process=p,
            infer=lambda payload: {"doubled": payload["value"] * 2})
        replica_procs.append(p)
        replicas.append(replica)

    pr = make_process(engine, 99)
    router = compose_instance(ReplicaRouter, actor_args("router"),
                              process=pr)
    engine.drain()
    assert router.share["replicas"] == 3

    responses = []
    response_topic = "test/h/99/client/response"
    collect_responses(pr, response_topic, responses)

    for i in range(9):
        pr.message.publish(
            f"{router.topic_path}/in",
            generate("infer", [f"req{i}", response_topic,
                               encode_swag({"value": np.int64(i)})]))
    engine.drain()
    assert len(responses) == 9
    assert sorted(int(v["doubled"]) for _, v in responses) == \
        [2 * i for i in range(9)]
    served = [r.share["requests_served"] for r in replicas]
    assert served == [3, 3, 3]        # perfect round-robin

    # Kill one replica process: LWT -> registrar eviction -> router prune.
    replica_procs[0].kill()
    engine.drain()
    assert router.share["replicas"] == 2

    responses.clear()
    for i in range(4):
        pr.message.publish(
            f"{router.topic_path}/in",
            generate("infer", [f"again{i}", response_topic,
                               encode_swag({"value": np.int64(i)})]))
    engine.drain()
    assert len(responses) == 4        # only live replicas were used


def test_router_reports_no_replicas(engine):
    p0 = make_process(engine, 1, broker="empty")
    Registrar(process=p0)
    engine.advance(4.0)
    pr = make_process(engine, 2, broker="empty")
    router = compose_instance(ReplicaRouter, actor_args("router"),
                              process=pr)
    engine.drain()
    assert router.route("r1", "test/topic", {}) is False


def test_llama_replica_end_to_end(engine):
    p0 = make_process(engine, 1, broker="llm")
    Registrar(process=p0)
    engine.advance(4.0)

    p1 = make_process(engine, 2, broker="llm")
    compose_instance(ModelReplica, actor_args("llm_replica"), process=p1,
                     infer=make_llama_infer("tiny", max_new_tokens=4))
    pr = make_process(engine, 3, broker="llm")
    router = compose_instance(ReplicaRouter, actor_args("router"),
                              process=pr)
    engine.drain()
    assert router.share["replicas"] == 1

    responses = []
    response_topic = "test/h/3/client/response"
    collect_responses(pr, response_topic, responses)
    prompt = np.arange(1, 9, dtype=np.int32)[None, :]
    pr.message.publish(
        f"{router.topic_path}/in",
        generate("infer", ["chat1", response_topic,
                           encode_swag({"tokens": prompt})]))
    engine.drain()
    assert len(responses) == 1
    request_id, outputs = responses[0]
    assert request_id == "chat1"
    tokens_out = np.asarray(outputs["tokens_out"])
    assert tokens_out.shape == (1, 12)
    assert (tokens_out[:, :8] == prompt).all()


def test_llama_infer_rejects_overlong_prompt():
    """A prompt >= max_seq_len must come back as a clean error payload,
    not an opaque trace error from a too-short cache (ADVICE r1)."""
    from aiko_services_tpu.models import llama
    infer = make_llama_infer("tiny", max_new_tokens=4)
    too_long = llama.CONFIGS["tiny"].max_seq_len
    out = infer({"tokens": np.zeros((1, too_long), np.int32)})
    assert "error" in out and "max_seq_len" in out["error"]


def test_moe_int8_replica_end_to_end(engine):
    """The EP/MoE model family composes with the serving stack: an
    int8-quantized moe_tiny replica serves a chat request through the
    router (VERDICT r1 #10)."""
    p0 = make_process(engine, 1, broker="moellm")
    Registrar(process=p0)
    engine.advance(4.0)

    p1 = make_process(engine, 2, broker="moellm")
    compose_instance(
        ModelReplica, actor_args("moe_replica"), process=p1,
        infer=make_llama_infer("moe_tiny", quantize=True,
                               max_new_tokens=4))
    pr = make_process(engine, 3, broker="moellm")
    router = compose_instance(ReplicaRouter, actor_args("router"),
                              process=pr)
    engine.drain()
    assert router.share["replicas"] == 1

    responses = []
    response_topic = "test/h/3/client/response"
    collect_responses(pr, response_topic, responses)
    prompt = np.arange(1, 7, dtype=np.int32)[None, :]
    pr.message.publish(
        f"{router.topic_path}/in",
        generate("infer", ["moe1", response_topic,
                           encode_swag({"tokens": prompt})]))
    engine.drain()
    assert len(responses) == 1
    request_id, outputs = responses[0]
    assert request_id == "moe1"
    tokens_out = np.asarray(outputs["tokens_out"])
    assert tokens_out.shape == (1, 10)
    assert (tokens_out[:, :6] == prompt).all()


def test_load_generator_against_continuous_replica(engine):
    """Open-loop load through the wire protocol: all requests complete,
    latencies recorded, error payloads counted separately."""
    from aiko_services_tpu.orchestration.continuous import (
        ContinuousBatchingServer, ContinuousReplica,
    )
    from aiko_services_tpu.tools import LoadGenerator

    process = make_process(engine, 31, broker="load")
    server = ContinuousBatchingServer(config_name="tiny", slots=4,
                                      max_seq=64, chunk_steps=4)
    replica = compose_instance(
        ContinuousReplica, actor_args("cb_load"), process=process,
        server=server)

    clock = engine._clock
    generator = LoadGenerator(
        process, target_topic=replica.topic_in,
        payload_fn=lambda i: {"tokens": np.arange(1, 6 + (i % 3),
                                                  dtype=np.int32),
                              "max_new_tokens": 4},
        rate_hz=100.0, clock=clock.now, sleep=engine.advance)
    report = generator.run(12, drain_timeout_s=60.0,
                           pump=engine.drain)
    assert report.completed == 12, report
    assert report.timeouts == 0 and report.errors == 0
    assert report.p50_ms >= 0.0 and len(report.latencies_ms) == 12

    # Error payload (missing tokens) counts as error, not timeout.
    bad = LoadGenerator(
        process, target_topic=replica.topic_in,
        payload_fn=lambda i: {"max_new_tokens": 4},
        rate_hz=100.0, clock=clock.now, sleep=engine.advance)
    bad_report = bad.run(2, drain_timeout_s=30.0, pump=engine.drain)
    assert bad_report.errors == 2 and bad_report.timeouts == 0


def test_speculative_replica_matches_plain_replica(engine):
    """A speculative replica and a plain greedy replica serve the SAME
    prompt over the wire and return IDENTICAL tokens (greedy
    speculative decoding is exact) — so a router can mix them freely.
    The speculative response also carries acceptance stats."""
    p0 = make_process(engine, 1, broker="spec")
    Registrar(process=p0)
    engine.advance(4.0)

    p1 = make_process(engine, 2, broker="spec")
    plain = compose_instance(
        ModelReplica, actor_args("plain"), process=p1,
        infer=make_llama_infer("tiny", max_new_tokens=10))
    p2 = make_process(engine, 3, broker="spec")
    spec = compose_instance(
        ModelReplica, actor_args("spec"), process=p2,
        infer=make_speculative_infer(
            target_config="tiny", draft_config="tiny",
            max_new_tokens=10, k=3, seed=0, draft_seed=7))

    pr = make_process(engine, 99, broker="spec")
    responses = []
    response_topic = "test/h/99/client/response"
    collect_responses(pr, response_topic, responses)
    prompt = np.asarray([5, 17, 200, 3, 9], np.int32)
    for name, replica in (("plain", plain), ("spec", spec)):
        pr.message.publish(
            f"{replica.topic_path}/in",
            generate("infer", [name, response_topic,
                               encode_swag({"tokens": prompt,
                                            "max_new_tokens":
                                            np.int64(10)})]))
    engine.drain()
    by_id = dict(responses)
    assert set(by_id) == {"plain", "spec"}
    np.testing.assert_array_equal(by_id["plain"]["tokens_out"],
                                  by_id["spec"]["tokens_out"])
    assert 0.0 <= float(by_id["spec"]["acceptance_rate"]) <= 1.0
    assert float(by_id["spec"]["tokens_per_target_pass"]) >= 1.0


def test_constrained_replica_grammatical_over_wire(engine):
    """A constrained replica serves requests whose outputs the grammar
    MUST accept — verified by replaying every returned sequence through
    the automaton, over the actual wire protocol."""
    from aiko_services_tpu.models.constrained import automaton_from_rules
    from aiko_services_tpu.orchestration.serving import (
        make_constrained_infer,
    )
    LP, RP = 1, 2
    automaton = automaton_from_rules(
        vocab=1024,
        rules={0: [((LP,), 1)], 1: [((3, 4, 5), 2)],
               2: [((6, 7, 8, 9), 4), ((RP,), 3)],
               4: [((RP,), 3)], 3: []},
        accepting=[3])

    p1 = make_process(engine, 2, broker="grammar")
    replica = compose_instance(
        ModelReplica, actor_args("grammar_replica"), process=p1,
        infer=make_constrained_infer("tiny", automaton=automaton,
                                     max_new_tokens=8,
                                     temperature=1.0))
    pr = make_process(engine, 3, broker="grammar")
    responses = []
    response_topic = "test/h/3/client/response"
    collect_responses(pr, response_topic, responses)
    prompt = np.asarray([[30, 40, 50, 60]], np.int32)
    pr.message.publish(
        f"{replica.topic_path}/in",
        generate("infer", ["g1", response_topic,
                           encode_swag({"tokens": prompt,
                                        "seed": np.int64(9)})]))
    engine.drain()
    assert len(responses) == 1
    _, outputs = responses[0]
    out = np.asarray(outputs["tokens_out"])[0].tolist()
    assert np.asarray(outputs["accepted"]).all()
    close = out.index(RP)
    assert automaton.accepts(out[:close + 1])
    assert all(t == 0 for t in out[close + 1:])
